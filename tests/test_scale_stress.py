"""Control-plane scale stress (reference: release/benchmarks — many_nodes,
many_actors, many_tasks — shrunk to CI scale but exercising the same
tables, schedulers, and persistence paths at 10-100x the rest of the
suite's counts).

Virtual nodes register directly with the GCS (no worker processes — the
point is control-plane load, reference fake_multi_node); the task stress
runs against a real node manager.
"""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu.protobuf import ray_tpu_pb2 as pb


def _start_gcs(tmp_path):
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [os.getcwd()] + sys.path),
        RAY_TPU_GCS_PERSIST_PATH=str(tmp_path / "gcs.snap"))
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs.server", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True)
    while True:
        line = proc.stdout.readline().strip()
        if line.startswith("GCS_PORT="):
            return proc, f"127.0.0.1:{int(line.split('=', 1)[1])}"
        if not line and proc.poll() is not None:
            raise RuntimeError(
                f"GCS subprocess died at startup (rc={proc.returncode})")


def _fresh_stub(address):
    rpc.drop_stub("GcsService", address)
    return rpc.get_stub("GcsService", address)


NUM_VIRTUAL_NODES = 500
NUM_ACTORS = 5000
NUM_OBJECTS = 25000


def test_control_plane_scale_and_wal_replay_under_load(tmp_path):
    """500 virtual nodes + 5k actors + 25k objects of directory/refcount
    state, then a hard GCS kill (no graceful compaction) and restart:
    the WAL must replay everything."""
    proc, address = _start_gcs(tmp_path)
    gcs = _fresh_stub(address)
    # Virtual nodes must heartbeat or the 3s health TTL (correctly)
    # reaps them — and marks their actors DEAD — mid-load at this scale.
    import threading

    hb_stop = threading.Event()

    def _heartbeater():
        seq = 0
        stub = rpc.get_stub("GcsService", address)
        while not hb_stop.wait(1.0):
            seq += 1
            for i in range(NUM_VIRTUAL_NODES):
                try:
                    stub.Heartbeat(pb.HeartbeatRequest(
                        node_id=f"{i:032x}", seq=seq))
                except Exception:  # noqa: BLE001 — GCS mid-restart
                    break

    hb_thread = threading.Thread(target=_heartbeater, daemon=True)
    try:
        t0 = time.monotonic()
        for i in range(NUM_VIRTUAL_NODES):
            info = pb.NodeInfo(node_id=f"{i:032x}",
                               address=f"127.0.0.1:{20000 + i}", alive=True)
            info.resources["CPU"] = 8.0
            info.available["CPU"] = 8.0
            gcs.RegisterNode(pb.RegisterNodeRequest(info=info))
        hb_thread.start()
        nodes = gcs.GetNodes(pb.GetNodesRequest()).nodes
        assert sum(1 for n in nodes if n.alive) == NUM_VIRTUAL_NODES
        print(f"registered {NUM_VIRTUAL_NODES} nodes in "
              f"{time.monotonic() - t0:.1f}s")

        t0 = time.monotonic()
        for i in range(NUM_ACTORS):
            info = pb.ActorInfo(
                actor_id=i.to_bytes(16, "big"), class_name="Stress",
                name=f"actor-{i}" if i % 10 == 0 else "",
                namespace="stress", state="ALIVE",
                node_id=f"{i % NUM_VIRTUAL_NODES:032x}",
                address="127.0.0.1:1")
            gcs.UpdateActor(pb.UpdateActorRequest(info=info))
        listed = gcs.ListActors(pb.ListActorsRequest(
            namespace="stress")).actors
        assert len(listed) == NUM_ACTORS
        print(f"registered {NUM_ACTORS} actors in "
              f"{time.monotonic() - t0:.1f}s")

        t0 = time.monotonic()
        batch = pb.ObjectLocationBatch()
        for i in range(NUM_OBJECTS):
            batch.updates.append(pb.ObjectLocationUpdate(
                object_id=i.to_bytes(28, "big"),
                node_id=f"{i % NUM_VIRTUAL_NODES:032x}",
                added=True, size=1024))
            if len(batch.updates) == 500:
                gcs.UpdateObjectLocationsBatch(batch)
                batch = pb.ObjectLocationBatch()
        if batch.updates:
            gcs.UpdateObjectLocationsBatch(batch)
        req = pb.UpdateRefCountsRequest(holder_id="stress-driver",
                                        node_id="", is_driver=True)
        for i in range(NUM_OBJECTS):
            req.deltas.append(pb.RefCountDelta(
                object_id=i.to_bytes(28, "big"), delta=1))
        gcs.UpdateRefCounts(req)
        for i in range(200):  # kv churn
            gcs.KvPut(pb.KvRequest(ns="stress", key=f"k{i}",
                                   value=b"v" * 100, overwrite=True))
        print(f"directory/refs/kv load in {time.monotonic() - t0:.1f}s")
        time.sleep(1.0)  # let the WAL writer drain its queue

        # Hard kill: no graceful shutdown, no final compaction — recovery
        # must come from snapshot + WAL replay alone.
        hb_stop.set()
        proc.kill()
        proc.wait(timeout=10)

        proc, address = _start_gcs(tmp_path)
        gcs = _fresh_stub(address)
        t0 = time.monotonic()
        # Real nodes would reconnect and heartbeat immediately; the
        # virtual ones must too or the 3s health TTL (correctly) reaps
        # them — and their actors — mid-verification at this scale.
        for i in range(NUM_VIRTUAL_NODES):
            gcs.Heartbeat(pb.HeartbeatRequest(node_id=f"{i:032x}", seq=1))
        listed = gcs.ListActors(pb.ListActorsRequest(
            namespace="stress")).actors
        assert len(listed) == NUM_ACTORS, \
            f"only {len(listed)} actors survived restart"
        found = gcs.GetActor(pb.GetActorRequest(
            name="actor-500", namespace="stress"))
        assert found.found and found.info.state == "ALIVE"
        locs = gcs.GetObjectLocations(pb.GetObjectLocationsRequest(
            object_id=(42).to_bytes(28, "big")))
        assert list(locs.node_ids) == [f"{42 % NUM_VIRTUAL_NODES:032x}"]
        kv = gcs.KvGet(pb.KvRequest(ns="stress", key="k7"))
        assert kv.found and kv.value == b"v" * 100
        mem = gcs.KvGet(pb.KvRequest(ns="__memory__", key=""))
        import pickle

        report = pickle.loads(mem.value)
        assert report["num_tracked"] == NUM_OBJECTS
        print(f"restart + verify in {time.monotonic() - t0:.1f}s")
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_many_queued_tasks(tmp_path):
    """100k no-op tasks queued at once drain correctly (reference:
    many_tasks benchmark — the 1M envelope shrunk to CI scale)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_node_args={"num_cpus": 4})
    try:
        ray_tpu.init(address=c.address)

        @ray_tpu.remote(num_cpus=0)
        def nop(i):
            return i

        n = 100_000
        t0 = time.monotonic()
        refs = [nop.remote(i) for i in range(n)]
        submit_s = time.monotonic() - t0
        out = ray_tpu.get(refs, timeout=600)
        total_s = time.monotonic() - t0
        assert out == list(range(n))
        print(f"submitted {n} in {submit_s:.1f}s; drained in {total_s:.1f}s "
              f"({n / total_s:.0f} tasks/s)")
        assert total_s < 240, "100k tasks took too long"
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_many_placement_groups(tmp_path):
    """A thousand placement groups create, place, and remove cleanly
    (reference: placement_group stress in release/nightly_tests)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_node_args={"num_cpus": 1000})
    try:
        ray_tpu.init(address=c.address)
        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)

        n = 1000
        t0 = time.monotonic()
        pgs = [placement_group([{"CPU": 1}]) for _ in range(n)]
        for pg in pgs:
            ray_tpu.get(pg.ready(), timeout=300)
        create_s = time.monotonic() - t0
        avail = ray_tpu.available_resources().get("CPU", 0)
        assert avail == 0.0, f"expected all CPU reserved, {avail} free"
        t0 = time.monotonic()
        for pg in pgs:
            remove_placement_group(pg)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if ray_tpu.available_resources().get("CPU", 0) == 1000.0:
                break
            time.sleep(0.5)
        assert ray_tpu.available_resources().get("CPU", 0) == 1000.0
        print(f"created {n} PGs in {create_s:.1f}s; removed in "
              f"{time.monotonic() - t0:.1f}s")
    finally:
        ray_tpu.shutdown()
        c.shutdown()
