"""Tune PBT (exploit/explore with checkpoint forking) + RLlib DQN on a
multi-learner LearnerGroup.

Reference: ``python/ray/tune/schedulers/pbt.py``,
``rllib/core/learner/learner_group.py:80``, ``rllib/algorithms/dqn``.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.rllib import DQNConfig, DQNLearner, DQNModule, LearnerGroup
from ray_tpu.rllib.core import Transition


@pytest.fixture
def ray_local():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8, num_tpus=0)
    yield
    ray_tpu.shutdown()


# ----------------------------------------------------------------- PBT

def test_pbt_exploits_and_converges_to_good_hyperparam(ray_local):
    """Low-lr trials must clone a high-lr trial's checkpoint and perturbed
    config; the whole population ends near the good hyperparameter."""

    def trainable(config):
        ckpt = tune.get_checkpoint() or {"score": 0.0, "step": 0}
        score, step = ckpt["score"], ckpt["step"]
        import time as _t

        for _ in range(8 - step):
            step += 1
            score += config["lr"]  # higher lr -> strictly faster progress
            tune.report({"score": score, "lr": config["lr"]},
                        checkpoint={"score": score, "step": step})
            _t.sleep(0.15)  # keep trials in flight so the controller's
            # polls interleave (PBT exploits only mid-flight trials)

    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_mutations={"lr": [0.01, 0.1, 1.0]}, seed=0)
    grid = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.01, 0.02, 1.0, 1.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=pbt),
    ).fit()
    assert len(grid) == 4 and not grid.errors
    assert pbt.exploit_count >= 1, "PBT never exploited"
    best = grid.get_best_result()
    assert best.metrics["score"] >= 8 * 1.0 * 0.8  # a high-lr lineage won
    # The exploited trials' final lr moved toward the top performers'.
    final_lrs = [r.metrics["lr"] for r in grid if r.metrics]
    assert max(final_lrs) >= 0.8


def test_pbt_forked_trial_resumes_from_donor_checkpoint(ray_local):
    """The forked trial continues from the donor's step/score, not from
    zero (checkpoint forking, not a restart)."""
    seen = []

    def trainable(config):
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            # Only forked trials see a checkpoint; record what they got.
            tune.report({"score": 1000 + ckpt["score"],
                         "forked_from_step": ckpt["step"]},
                        checkpoint=ckpt)
            return
        import time as _t

        score, step = 0.0, 0
        for _ in range(8):
            step += 1
            score += config["lr"]
            tune.report({"score": score, "forked_from_step": -1},
                        checkpoint={"score": score, "step": step})
            _t.sleep(0.15)

    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_mutations={"lr": [1.0]}, seed=1)
    grid = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.001, 1.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=pbt),
    ).fit()
    assert not grid.errors
    forked = [r for r in grid if r.metrics
              and r.metrics.get("forked_from_step", -1) > 0]
    assert forked, "no trial resumed from a donor checkpoint"
    assert all(r.metrics["score"] >= 1000 for r in forked)


# ------------------------------------------------- DQN / LearnerGroup

def _synthetic_transitions(n, obs_dim, num_actions, seed):
    rng = np.random.default_rng(seed)
    return Transition(
        obs=rng.normal(size=(n, obs_dim)).astype(np.float32),
        actions=rng.integers(0, num_actions, size=n),
        rewards=rng.normal(size=n).astype(np.float32),
        next_obs=rng.normal(size=(n, obs_dim)).astype(np.float32),
        dones=(rng.random(n) < 0.1).astype(np.float32),
    )


def test_learner_group_keeps_replicas_identical(ray_local):
    """Two learners allreduce gradients each step, so their weights stay
    bit-identical without any broadcast."""

    def builder():
        return DQNLearner(DQNModule(obs_dim=4, num_actions=2, hidden=(16,)),
                          lr=1e-3, seed=7)

    group = LearnerGroup(builder, num_learners=2)
    w0 = group.get_weights()
    for i in range(4):
        group.update(_synthetic_transitions(64, 4, 2, seed=i))
    wa, wb = group.get_all_weights()
    import jax

    la = jax.tree.leaves(wa)
    lb = jax.tree.leaves(wb)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # And training actually moved the weights.
    moved = any(not np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(jax.tree.leaves(w0), la))
    assert moved


def test_dqn_learns_cartpole(ray_local):
    """Short-budget sanity: DQN's mean return must clearly beat a random
    policy (~20 on CartPole) after a few iterations."""
    pytest.importorskip("gymnasium")
    algo = (DQNConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                         rollout_fragment_length=64)
            .training(lr=1e-3, train_batch_size=128,
                      num_updates_per_iteration=32, learning_starts=256,
                      epsilon_decay_iterations=10, target_update_freq=50)
            .build())
    best = 0.0
    for _ in range(40):
        result = algo.train()
        if result["episode_return_mean"] == result["episode_return_mean"]:
            best = max(best, result["episode_return_mean"])
        if best >= 60:
            break
    algo.stop()
    assert best >= 60, f"DQN failed to learn: best mean return {best}"
