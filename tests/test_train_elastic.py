"""Elastic fault-tolerant training, driven by real injected faults.

Every recovery path of the elastic control loop (ISSUE 10) is exercised
through the deterministic chaos harness (``ray_tpu/_private/chaos.py``)
rather than mocks: a ``kill_worker`` rule raises inside the worker's
``run()`` thread and the in-process runtime converts it into genuine
actor death (``ActorDiedError`` on every pending call), ``slow_step``
wedges a step so the controller watchdog fires, ``drop_heartbeat``
silences the worker's liveness thread, and ``corrupt_shard`` /
``fail_shard_write`` attack the checkpoint plane — so what the
controller detects and recovers from is exactly what a real dead host /
hung collective / rotten disk would have produced.
"""

import json
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train as rt_train
from ray_tpu._private import chaos
from ray_tpu._private import metrics_defs as mdefs
from ray_tpu.checkpoint import CheckpointPlane
from ray_tpu.exceptions import CheckpointCorruptError, NaNLossError
from ray_tpu.train import (
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.trainer import ControllerState

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _chaos_reset():
    yield
    chaos.reset()


@pytest.fixture
def elastic_ray(monkeypatch):
    """In-process runtime + tight backoff so recoveries take ~ms."""
    monkeypatch.setenv("RAY_TPU_RESTART_BACKOFF_S", "0.05")
    monkeypatch.setenv("RAY_TPU_RESTART_BACKOFF_MAX_S", "0.2")
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def _triangle(k: int) -> float:
    """Every element of the state vector after completing step ``k-1``:
    the loop adds ``step+1`` each step, so this is 1+2+...+k."""
    return k * (k + 1) / 2.0


def _make_loop(total: int, width: int = 4, restores=None, resize_at=None,
               step_sleep: float = 0.0):
    """A deterministic elastic train loop: restores from the newest
    committed checkpoint-plane manifest, adds ``step+1`` to every element
    per step, saves + reports each step. State is a pure function of the
    completed step count, so restores are checked bit-identical against
    the closed form regardless of the topology they were saved on."""

    def loop(config):
        ctx = rt_train.get_context()
        plane = rt_train.get_checkpoint_plane()
        w = np.zeros(width, np.float64)
        start = 0
        if plane.latest_step() is not None:
            st = plane.restore()
            w, start = st["w"], int(st["step"]) + 1
            # Bit-identical cross-topology restore: the value must equal
            # the closed form for the step it was saved at, no matter
            # which world size wrote the shards.
            assert np.array_equal(w, np.full(width, _triangle(start))), (
                start, w)
            if restores is not None and ctx.get_world_rank() == 0:
                restores.append((ctx.get_world_size(), start))
        for step in range(start, total):
            if resize_at and ctx.get_world_rank() == 0:
                target = resize_at.get((ctx.get_world_size(), step))
                if target:
                    rt_train.request_resize(target)
            if step_sleep:
                time.sleep(step_sleep)
            w = w + (step + 1)
            plane.save(step, {"w": w, "step": np.asarray(step)})
            rt_train.report({"step": step, "loss": float(w.sum()),
                             "world": ctx.get_world_size()})
        return float(w.sum())

    return loop


def _fit(loop, tmp_path, name, num_workers=4, min_workers=1, **failure_kw):
    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=num_workers,
                                     min_workers=min_workers),
        run_config=RunConfig(
            name=name, storage_path=str(tmp_path),
            failure_config=FailureConfig(**failure_kw)),
    )
    return trainer, trainer.fit()


def _restart_count(cause: str) -> float:
    return sum(v for _n, key, v in mdefs.TRAIN_RESTARTS.samples()
               if ("cause", cause) in key)


# ---------------------------------------------------------------- e2e
def test_kill_worker_recovers_shrunk_then_grows_back(elastic_ray,
                                                     tmp_path):
    """The acceptance path end to end: a chaos-killed worker (whose node
    the cluster cannot replace — the kill publishes a world_target=2
    hint) triggers detection -> mesh re-formation at the reduced world
    size -> restore from the newest committed manifest -> training
    resumes; a later grow-back resize restores bit-identically
    cross-topology, and the final loss matches an uninterrupted run."""
    total = 10
    before = _restart_count("worker_lost")

    # Uninterrupted baseline run (no chaos installed yet).
    _t, baseline = _fit(_make_loop(total), tmp_path, "baseline")
    assert baseline.error is None
    uninterrupted_loss = baseline.metrics["loss"]

    chaos.configure("kill_worker:rank=1,step=3,resize=2", seed=7)
    restores = []
    # step_sleep keeps steps slower than the controller's poll cadence,
    # so the grow-back ask is seen while steps remain (the attempt
    # re-forms mid-run and reports at the grown world) regardless of
    # process warm-up.
    loop = _make_loop(total, restores=restores, step_sleep=0.03,
                      resize_at={(2, 6): 4})  # grow back at world 2, step 6
    trainer, result = _fit(loop, tmp_path, "chaotic")

    assert result.error is None
    assert trainer.controller_state == ControllerState.FINISHED
    assert ControllerState.RESTARTING in trainer.state_history
    # Detection really came from the injected fault.
    assert [e["action"] for e in chaos.injection_log()] == ["kill_worker"]
    causes = [r["cause"] for r in trainer.recovery_log]
    assert causes == ["worker_lost", "resize"]
    assert trainer.recovery_log[1]["world_target"] == 4
    # Shrink to 2, then re-formed at 4; each restore was bit-identical
    # (asserted inside the loop) and resumed from a committed step.
    assert [w for w, _s in restores] == [2, 4]
    assert all(s > 0 for _w, s in restores)
    worlds = [m["metrics"]["world"] for m in result.metrics_history]
    assert 2 in worlds and worlds[-1] == 4
    # Final loss matches the uninterrupted run exactly (deterministic
    # state; tolerance would only mask a restore bug).
    assert result.metrics["loss"] == uninterrupted_loss
    # Telemetry: restart counted under its cause, recovery time recorded,
    # world-size gauge ends at the grown-back size.
    assert _restart_count("worker_lost") == before + 1
    assert trainer.recovery_log[0].get("recovery_s", 0) > 0
    assert [v for _n, _k, v in mdefs.TRAIN_WORLD_SIZE.samples()][-1] == 4.0


def test_resize_shrink_then_grow_bit_identical(elastic_ray, tmp_path):
    """Operator-driven resize 4 -> 2 -> 4 with no failure: both
    re-formations charge the resize budget (no backoff) and every restore
    is bit-identical across topologies."""
    restores = []
    # Slow steps (see the kill test): both asks land mid-run.
    loop = _make_loop(10, restores=restores, step_sleep=0.03,
                      resize_at={(4, 2): 2, (2, 6): 4})
    trainer, result = _fit(loop, tmp_path, "resize")
    assert result.error is None
    assert [r["cause"] for r in trainer.recovery_log] == ["resize",
                                                          "resize"]
    assert all(r["backoff_s"] == 0.0 for r in trainer.recovery_log)
    assert [w for w, _s in restores] == [2, 4]
    assert result.metrics["loss"] == 4 * _triangle(10)


def test_unsatisfiable_resize_ask_does_not_livelock(elastic_ray,
                                                    tmp_path):
    """A world-target ask the cluster cannot fully satisfy re-forms the
    group ONCE at the best feasible size and clears its latch — it must
    not re-trigger a zero-backoff resize loop that burns
    RAY_TPU_MAX_RESIZES and errors a healthy run (the periodic grow
    probe finishes the job if capacity ever appears)."""
    loop = _make_loop(10, resize_at={(4, 3): 64})  # only 8 CPUs exist
    trainer, result = _fit(loop, tmp_path, "unsat")
    assert result.error is None
    assert trainer.controller_state == ControllerState.FINISHED
    assert [r["cause"] for r in trainer.recovery_log] == ["resize"]
    assert result.metrics["loss"] == 4 * _triangle(10)


def test_capacity_hint_does_not_preempt_train_loops():
    """GCS capacity hints and explicit world-target asks ride the
    PREEMPT channel but are ResizeGuard's to latch: a PreemptionGuard
    (the JIT-save path inside every running train loop) must ignore
    them, or each capacity rise would spuriously preempt every job."""
    from ray_tpu.checkpoint.preempt import PreemptionGuard, notify_preemption
    from ray_tpu.train.elastic import ResizeGuard

    with PreemptionGuard() as pguard, ResizeGuard() as rguard:
        notify_preemption({"reason": "capacity-grew", "kind": "capacity",
                           "node": "*"})
        notify_preemption({"reason": "operator-resize", "world_target": 6,
                           "node": "*"})
        assert not pguard.triggered
        assert rguard.target == 6
        notify_preemption({"reason": "host-preempted", "node": "*"})
        assert pguard.triggered


def test_hung_step_watchdog_fires_and_recovers(elastic_ray, tmp_path):
    """A chaos-wedged step (hung collective) stalls the report stream
    while heartbeats keep flowing; the per-step watchdog turns the stall
    into a retryable hang and the run resumes from the newest committed
    manifest."""
    chaos.configure("slow_step:rank=0,step=2,secs=1.6")
    trainer, result = _fit(_make_loop(5), tmp_path, "hang",
                           watchdog_s=0.5)
    assert result.error is None
    assert [r["cause"] for r in trainer.recovery_log] == ["hang"]
    assert "watchdog" in trainer.recovery_log[0]["error"]
    assert result.metrics["step"] == 4


def test_heartbeat_lapse_detected(elastic_ray, tmp_path, monkeypatch):
    """Chaos-dropped heartbeats (worker alive but silent) trip the
    heartbeat TTL even though the actor channel still answers polls."""
    monkeypatch.setenv("RAY_TPU_TRAIN_HEARTBEAT_S", "0.1")
    monkeypatch.setenv("RAY_TPU_TRAIN_HEARTBEAT_TTL_S", "0.5")
    chaos.configure("drop_heartbeat:rank=0,times=12")
    trainer, result = _fit(
        _make_loop(16, step_sleep=0.12), tmp_path, "hb", num_workers=1)
    assert result.error is None
    assert trainer.recovery_log[0]["cause"] == "hang"
    assert "heartbeat" in trainer.recovery_log[0]["error"]
    assert result.metrics["step"] == 15


def test_backoff_schedule_respected(elastic_ray, tmp_path):
    """Consecutive zero-progress worker losses back off exponentially
    from RAY_TPU_RESTART_BACKOFF_S up to the cap (0.05 -> 0.1 -> 0.2
    under the fixture's knobs)."""
    chaos.configure("kill_worker:rank=0,times=3")
    trainer, result = _fit(_make_loop(4), tmp_path, "backoff",
                           num_workers=1)
    assert result.error is None
    assert [r["backoff_s"] for r in trainer.recovery_log] == [0.05, 0.1,
                                                              0.2]
    assert [r["budget"] for r in trainer.recovery_log] == ["1/16", "2/16",
                                                           "3/16"]


def test_restart_budget_exhausts(elastic_ray, tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_MAX_RESTARTS", "2")
    chaos.configure("kill_worker:rank=0,times=100")
    trainer, result = _fit(_make_loop(4), tmp_path, "exhaust",
                           num_workers=1)
    assert result.error is not None
    assert trainer.controller_state == ControllerState.ERRORED
    assert len(trainer.recovery_log) == 2  # the third loss ended the run


def test_user_failure_charges_max_failures_not_restart_budget(
        elastic_ray, tmp_path, monkeypatch):
    """A user exception is governed by FailureConfig.max_failures exactly
    as before — with the infrastructure restart budget pinned to ZERO the
    run still retries (and succeeds), proving user failures never draw
    from the restart budget."""
    monkeypatch.setenv("RAY_TPU_MAX_RESTARTS", "0")
    marker = tmp_path / "failed_once"

    def loop(config):
        inner = _make_loop(3, width=2)
        if not marker.exists():
            marker.write_text("x")
            rt_train.report({"step": -1, "loss": 0.0, "world": 1})
            raise RuntimeError("user train loop bug")
        return inner(config)

    trainer, result = _fit(loop, tmp_path, "userfail", num_workers=1,
                           max_failures=1)
    assert result.error is None
    assert [r["cause"] for r in trainer.recovery_log] == ["user"]
    assert trainer.recovery_log[0]["budget"] == "1/1"


def test_fatal_nan_does_not_consume_any_budget(elastic_ray, tmp_path):
    """Repeated non-finite loss is FATAL: restarting would replay the
    same divergence, so the run errors out with zero recoveries and no
    restart counted."""
    before = sum(v for _n, _k, v in mdefs.TRAIN_RESTARTS.samples())

    def loop(config):
        for step in range(10):
            time.sleep(0.01)
            rt_train.report({"step": step, "loss": float("nan")})

    trainer, result = _fit(loop, tmp_path, "nan", num_workers=1,
                           nan_fatal_reports=3)
    assert isinstance(result.error, NaNLossError)
    assert trainer.controller_state == ControllerState.ERRORED
    assert trainer.recovery_log == []
    assert sum(v for _n, _k, v in mdefs.TRAIN_RESTARTS.samples()) == before


# ------------------------------------------------- chaos harness itself
def test_chaos_same_seed_replays_same_fault_sequence():
    spec = "slow_step:p=0.5,times=1000,secs=0"

    def run(seed):
        chaos.configure(spec, seed=seed)
        for rank in range(2):
            for step in range(20):
                chaos.inject("train_step", rank=rank, step=step)
        return {(e["coords"]["rank"], e["coords"]["step"])
                for e in chaos.injection_log()}

    a, b, c = run(7), run(7), run(11)
    assert a == b  # deterministic replay
    assert a != c  # a different seed explores a different sequence
    assert 0 < len(a) < 40


def test_chaos_exact_rule_fires_once_at_its_coordinates():
    chaos.configure("slow_step:rank=1,step=3,secs=0")
    for _ in range(3):
        for rank in range(2):
            for step in range(5):
                chaos.inject("train_step", rank=rank, step=step)
    log = chaos.injection_log()
    assert len(log) == 1
    assert log[0]["coords"] == {"rank": 1, "step": 3}


def _fired(directives):
    """Every firing carries its flight-recorder event id; strip it so
    the cooperative-directive payload can be compared exactly."""
    assert directives is not None and directives.pop("event_id")
    return directives


def test_chaos_cooperative_sites_return_directives():
    chaos.configure("drop_node_hb;drop_agent_vitals;"
                    "drop_heartbeat:rank=0;"
                    "delay_heartbeat:rank=1,secs=0.01")
    assert _fired(chaos.inject("node_heartbeat",
                               node="abc")) == {"drop": True}
    assert chaos.inject("node_heartbeat", node="abc") is None  # times=1
    assert _fired(chaos.inject("agent_vitals",
                               node="abc")) == {"drop": True}
    assert _fired(chaos.inject("train_heartbeat",
                               rank=0)) == {"drop": True}
    assert _fired(chaos.inject("train_heartbeat",
                               rank=1)) == {"delay_s": 0.01}
    assert chaos.inject("train_heartbeat", rank=2) is None


def test_chaos_env_activation(monkeypatch):
    monkeypatch.setenv("RAY_TPU_CHAOS", "slow_step:rank=0,step=1,secs=0")
    monkeypatch.setenv("RAY_TPU_CHAOS_SEED", "13")
    chaos.reset()  # force the env to be re-read
    plan = chaos.current_plan()
    assert plan is not None and plan.seed == 13
    # slow_step acts in place (sleeps) and reports the applied delay.
    assert _fired(chaos.inject("train_step",
                               rank=0, step=1)) == {"slept_s": 0.0}
    assert [e["action"] for e in chaos.injection_log()] == ["slow_step"]


# ------------------------------------------- checkpoint shard integrity
def test_shard_crc_recorded_and_corruption_falls_back(tmp_path):
    plane = CheckpointPlane(str(tmp_path), run="integrity",
                            process_index=0, process_count=1)
    plane.save(0, {"w": np.arange(4.0)})
    chaos.configure("corrupt_shard:step=1")
    plane.save(1, {"w": np.arange(4.0) * 2})  # commits, then rots
    assert plane.steps() == [0, 1]
    spec_path = os.path.join(plane.step_dir(0),
                             "shard-00000-of-00001.json")
    assert "crc32" in json.load(open(spec_path))
    # Newest manifest is corrupt: both readers fall back to step 0.
    restored = plane.restore()
    assert np.array_equal(restored["w"], np.arange(4.0))
    from ray_tpu.checkpoint.plane import load_latest

    assert np.array_equal(
        load_latest(str(tmp_path), run="integrity")["w"], np.arange(4.0))
    # An explicitly requested corrupt step still raises.
    with pytest.raises(CheckpointCorruptError):
        plane.restore(step=1)


def test_failed_shard_write_never_commits(tmp_path):
    plane = CheckpointPlane(str(tmp_path), run="wfail",
                            process_index=0, process_count=1)
    plane.save(0, {"w": np.ones(3)})
    chaos.configure("fail_shard_write:step=1")
    with pytest.raises(OSError):
        plane.save(1, {"w": np.ones(3) * 2})
    # The failed write stayed invisible; readers see step 0 only.
    assert plane.latest_step() == 0
    assert np.array_equal(plane.restore()["w"], np.ones(3))


def test_trainer_falls_back_past_corrupt_newest_manifest(elastic_ray,
                                                         tmp_path):
    """Recovery restores from the newest *intact* committed manifest:
    the shard saved right before the kill is chaos-corrupted, so the
    restart must fall back one step further and recompute."""
    chaos.configure("corrupt_shard:step=4;kill_worker:rank=0,step=4")
    restores = []
    trainer, result = _fit(_make_loop(6, restores=restores), tmp_path,
                           "rotten", num_workers=1)
    assert result.error is None
    assert [r["cause"] for r in trainer.recovery_log] == ["worker_lost"]
    # Step 4 committed but rotted -> resumed from step 3 (start == 4),
    # not from the corrupt step 4 (start == 5).
    assert restores == [(1, 4)]
    assert result.metrics["loss"] == 4 * _triangle(6)


@pytest.mark.slow
def test_resize_soak_ladder(elastic_ray, tmp_path):
    """Long resize soak: repeated shrink/grow re-formations interleaved
    with a worker kill, every restore bit-identical (checked in-loop)."""
    chaos.configure("kill_worker:rank=1,step=12,resize=2", seed=3)
    restores = []
    loop = _make_loop(30, restores=restores,
                      resize_at={(4, 4): 3, (3, 8): 4, (2, 16): 3,
                                 (3, 22): 4})
    trainer, result = _fit(loop, tmp_path, "soak")
    assert result.error is None
    assert len(trainer.recovery_log) >= 4
    assert result.metrics["loss"] == 4 * _triangle(30)
    assert [w for w, _s in restores][-1] == 4
