"""Paged decode attention + KV arena: kernel parity, quantization
round-trip, and block-allocator lifecycle.

Tier-1 runs on CPU: the ``pallas_interpret`` fixture pins interpret mode
so the real paged kernel (scalar-prefetch block-table gather) executes
without TPU-only skips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.paged_kv import (GARBAGE_BLOCK, BlockAllocator,
                                     PagedKVCache, quantize_kv,
                                     resolve_kv_dtype)
from ray_tpu.ops.decode_attention import decode_attention_reference
from ray_tpu.ops.paged_decode_attention import (paged_applicable,
                                                paged_attention_reference,
                                                paged_decode_attention)


def _paged_inputs(b=3, hq=4, hkv=2, d=16, bs=32, nb_slot=4, seed=0,
                  dtype=jnp.float32, scramble=True):
    """Dense K/V plus an equivalent scattered arena + block tables.

    The arena places each slot's logical blocks at arbitrary physical
    ids (permuted) so a passing test proves the TABLE gather, not a
    lucky identity layout. Returns (q, dense_ck, dense_cv, arena_k,
    arena_v, tables, positions)."""
    s_max = bs * nb_slot
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, hq, d), jnp.float32).astype(dtype)
    ck = jax.random.normal(ks[1], (b, s_max, hkv, d), jnp.float32)
    cv = jax.random.normal(ks[2], (b, s_max, hkv, d), jnp.float32)
    ck, cv = ck.astype(dtype), cv.astype(dtype)
    nb_total = b * nb_slot + 1                    # + garbage block 0
    ids = np.arange(1, nb_total)
    if scramble:
        ids = np.random.default_rng(seed).permutation(ids)
    tables = ids.reshape(b, nb_slot).astype(np.int32)
    arena_k = np.zeros((nb_total, bs, hkv, d), np.asarray(ck).dtype)
    arena_v = np.zeros_like(arena_k)
    for i in range(b):
        for j in range(nb_slot):
            arena_k[tables[i, j]] = np.asarray(ck[i, j * bs:(j + 1) * bs])
            arena_v[tables[i, j]] = np.asarray(cv[i, j * bs:(j + 1) * bs])
    return (q, ck, cv, jnp.asarray(arena_k), jnp.asarray(arena_v),
            jnp.asarray(tables), None)


# --------------------------------------------------- reference vs dense

def test_paged_reference_equals_dense_reference():
    """The paged reference (table gather -> dense attention) is exactly
    the dense reference over the linearized blocks — the parity anchor
    the kernel ships against."""
    q, ck, cv, ak, av, tables, _ = _paged_inputs()
    pos = jnp.asarray([0, 37, 127], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(paged_attention_reference(q, ak, av, tables, pos)),
        np.asarray(decode_attention_reference(q, ck, cv, pos)))


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 2)])
def test_paged_kernel_matches_reference_gqa(pallas_interpret, hq, hkv):
    q, ck, cv, ak, av, tables, _ = _paged_inputs(hq=hq, hkv=hkv)
    # Edge positions included: 0 (one live entry) and s_max-1 (full).
    pos = jnp.asarray([0, 17, 127], jnp.int32)
    ref = decode_attention_reference(q, ck, cv, pos)
    out = paged_decode_attention(q, ak, av, tables, pos, use_kernel=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6)


def test_paged_kernel_ragged_lengths_straddle_blocks(pallas_interpret):
    """Live lengths landing just before/on/after block boundaries: the
    per-block skip guard and the in-block causal mask must agree with
    the dense mask at every straddle."""
    q, ck, cv, ak, av, tables, _ = _paged_inputs(b=5, bs=32, nb_slot=4,
                                                 seed=3)
    # positions: last-in-block, first-in-next-block, mid-block, exactly
    # one full block, and the final position.
    pos = jnp.asarray([31, 32, 45, 63, 127], jnp.int32)
    ref = decode_attention_reference(q, ck, cv, pos)
    out = paged_decode_attention(q, ak, av, tables, pos, use_kernel=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6)


def test_paged_kernel_dead_tail_repeats_last_block(pallas_interpret):
    """Dead table entries repeating the last live block (the no-refetch
    bandwidth trick) must not change the output — they are masked."""
    q, ck, cv, ak, av, tables, _ = _paged_inputs()
    pos = jnp.asarray([5, 40, 70], jnp.int32)
    t = np.asarray(tables).copy()
    for i, p in enumerate([5, 40, 70]):
        last_live = p // 32
        t[i, last_live + 1:] = t[i, last_live]   # repeat last live block
    out_rep = paged_decode_attention(q, ak, av, jnp.asarray(t), pos,
                                     use_kernel=True)
    ref = decode_attention_reference(q, ck, cv, pos)
    np.testing.assert_allclose(np.asarray(out_rep), np.asarray(ref),
                               atol=2e-6)


def test_paged_kernel_bf16_arena(pallas_interpret):
    q, ck, cv, ak, av, tables, _ = _paged_inputs(dtype=jnp.bfloat16)
    pos = jnp.asarray([3, 50, 100], jnp.int32)
    ref = decode_attention_reference(q, ck, cv, pos)
    out = paged_decode_attention(q, ak, av, tables, pos, use_kernel=True)
    np.testing.assert_allclose(
        np.asarray(out, jnp.float32), np.asarray(ref, jnp.float32),
        atol=2e-2)


# ------------------------------------------------------------ int8 arena

def test_int8_quantize_roundtrip_tolerance():
    """Per-token/per-head symmetric int8: worst-case round-trip error is
    bounded by scale/2 = amax/254 per element; zero vectors survive
    exactly."""
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 64)) * 3.0
    x = x.at[1].set(0.0)
    q, scale = quantize_kv(x)
    assert q.dtype == jnp.int8
    back = q.astype(jnp.float32) * scale[..., None]
    amax = np.asarray(jnp.max(jnp.abs(x), axis=-1, keepdims=True))
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               atol=float(amax.max()) / 254 + 1e-7)
    np.testing.assert_array_equal(np.asarray(back[1]),
                                  np.zeros_like(np.asarray(back[1])))


@pytest.mark.parametrize("use_kernel", [False, True])
def test_paged_int8_attention_close_to_fp32(pallas_interpret, use_kernel):
    """int8 arena + per-token scales: attention output stays within
    quantization tolerance of the fp32 dense reference, kernel and
    reference dispatch agreeing with each other much tighter."""
    q, ck, cv, ak, av, tables, _ = _paged_inputs(seed=5)
    pos = jnp.asarray([9, 33, 120], jnp.int32)
    kq, ks = quantize_kv(ak)
    vq, vs = quantize_kv(av)
    out = paged_decode_attention(q, kq, vq, tables, pos, k_scale=ks,
                                 v_scale=vs, use_kernel=use_kernel)
    ref = decode_attention_reference(q, ck, cv, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=0.05, rtol=0.05)
    # Kernel vs reference on identical quantized inputs: tight.
    other = paged_decode_attention(q, kq, vq, tables, pos, k_scale=ks,
                                   v_scale=vs, use_kernel=not use_kernel)
    np.testing.assert_allclose(np.asarray(out), np.asarray(other),
                               atol=2e-6)


def test_paged_cache_create_dtypes():
    from ray_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
    dense = PagedKVCache.create(cfg, num_blocks=9, block_size=16)
    assert not dense.quantized and dense.k_scale is None
    assert dense.k.shape[1:3] == (9, 16)
    q8 = PagedKVCache.create(cfg, num_blocks=9, block_size=16,
                             kv_dtype="int8")
    assert q8.quantized and q8.k.dtype == jnp.int8
    assert q8.k_scale.shape == q8.k.shape[:-1]
    assert q8.token_bytes() < dense.token_bytes()
    with pytest.raises(ValueError):
        resolve_kv_dtype("fp4")


# ------------------------------------------------------- block allocator

def test_allocator_reuse_after_release():
    """Freed blocks return to the pool and are handed out again;
    all-or-nothing alloc leaves the pool untouched on failure."""
    a = BlockAllocator(num_blocks=8)            # 7 usable (0 reserved)
    first = a.alloc(4)
    assert len(first) == 4 and GARBAGE_BLOCK not in first
    second = a.alloc(3)
    assert a.free_count == 0 and a.used_count == 7
    assert a.alloc(1) is None                    # exhausted: no partial
    a.free(first)
    assert a.free_count == 4
    again = a.alloc(4)
    assert sorted(again) == sorted(first), "freed blocks not reused"
    assert a.alloc(1) is None
    a.free(second)
    a.free(again)
    assert a.free_count == 7 and a.used_count == 0


def test_allocator_zero_and_param_validation():
    a = BlockAllocator(num_blocks=4)
    assert a.alloc(0) == []            # must NOT drain the free list
    assert a.free_count == 3
    from ray_tpu.models.sampling import SamplingParams
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(temperature=0.7, top_p=0.0)
    with pytest.raises(ValueError, match="temperature"):
        SamplingParams(temperature=-1.0)


def test_allocator_rejects_bad_frees():
    a = BlockAllocator(num_blocks=4)
    got = a.alloc(2)
    with pytest.raises(ValueError):
        a.free([GARBAGE_BLOCK])
    with pytest.raises(ValueError):
        a.free([99])
    a.free(got)
    with pytest.raises(ValueError):
        a.free(got)                              # double free


def test_applicability_predicate():
    assert paged_applicable(64, 128, 16, 16)
    assert paged_applicable(32, 128, 32, 8)
    assert not paged_applicable(64, 96, 16, 16)   # d % 128
    assert not paged_applicable(64, 128, 16, 3)   # hq % hkv
    assert not paged_applicable(24, 128, 16, 16)  # block % 32
    # Auto mode on CPU routes to the reference (no kernel, no error).
    q, ck, cv, ak, av, tables, _ = _paged_inputs()
    pos = jnp.asarray([0, 1, 2], jnp.int32)
    out = paged_decode_attention(q, ak, av, tables, pos)  # auto
    np.testing.assert_array_equal(
        np.asarray(out),
        np.asarray(decode_attention_reference(q, ck, cv, pos)))
