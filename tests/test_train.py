"""End-to-end tests for ray_tpu.train (reference: python/ray/train/tests)."""

import os
import tempfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train as rt_train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    load_pytree,
    save_pytree,
)


@pytest.fixture
def ray8():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_single_worker_reports_and_result(ray8):
    def loop(config):
        ctx = rt_train.get_context()
        assert ctx.get_world_size() == 1
        for step in range(3):
            rt_train.report({"step": step, "loss": 1.0 / (step + 1)})

    trainer = JaxTrainer(loop, train_loop_config={},
                         scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_multi_worker_ranks(ray8):
    def loop(config):
        ctx = rt_train.get_context()
        rt_train.report({"rank": ctx.get_world_rank(),
                         "world": ctx.get_world_size()})

    trainer = JaxTrainer(loop, train_loop_config={},
                         scaling_config=ScalingConfig(num_workers=4))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world"] == 4


def test_checkpointing_and_topk(ray8, tmp_path):
    def loop(config):
        for step in range(5):
            d = tempfile.mkdtemp()
            save_pytree({"step": np.asarray(step)}, d)
            rt_train.report({"score": float(step)},
                            checkpoint=Checkpoint.from_directory(d))

    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score"),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    best = load_pytree(result.checkpoint.path)
    assert int(best["step"]) == 4
    ckpt_root = os.path.join(result.path, "checkpoints")
    assert len(os.listdir(ckpt_root)) == 2  # top-k retention


def test_failure_recovery_restores_from_checkpoint(ray8, tmp_path):
    marker = tmp_path / "crashed_once"

    def loop(config):
        ckpt = rt_train.get_checkpoint()
        start = int(load_pytree(ckpt.path)["step"]) + 1 if ckpt else 0
        for step in range(start, 4):
            if step == 2 and not marker.exists():
                marker.write_text("x")
                raise RuntimeError("simulated worker failure")
            d = tempfile.mkdtemp()
            save_pytree({"step": np.asarray(step)}, d)
            rt_train.report({"step": step},
                            checkpoint=Checkpoint.from_directory(d))

    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    # Restarted from step-1 checkpoint: steps 2, 3 ran after recovery.
    assert result.metrics["step"] == 3


def test_failure_exhausts_retries(ray8, tmp_path):
    def loop(config):
        raise RuntimeError("always fails")

    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.error is not None


def test_training_integration_with_sharded_trainer(ray8, tmp_path):
    """The BASELINE 'minimum slice': JaxTrainer driving the GSPMD train step."""

    def loop(config):
        import jax
        from ray_tpu.models import llama
        from ray_tpu.models.training import (
            ShardedTrainer, default_optimizer, synthetic_batch)
        from ray_tpu.parallel import MeshConfig, make_mesh

        cfg = llama.LlamaConfig.tiny()
        mesh = make_mesh(MeshConfig(fsdp=-1))
        trainer = ShardedTrainer(
            cfg, mesh,
            optimizer=default_optimizer(warmup_steps=1, total_steps=20,
                                        learning_rate=1e-2))
        state = trainer.init_state(0)
        batch = trainer.shard_batch(synthetic_batch(8, 64, cfg.vocab_size))
        for step in range(5):
            state, metrics = trainer.train_step(state, batch)
            rt_train.report({"loss": float(metrics["loss"]), "step": step})

    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    losses = [h["metrics"]["loss"] for h in result.metrics_history]
    assert losses[-1] < losses[0]


# -------------------------------------- controller state machine + elastic

def test_controller_state_machine(ray_start_regular):
    from ray_tpu.train import JaxTrainer, ScalingConfig, session
    from ray_tpu.train.trainer import ControllerState

    def loop():
        session.report({"x": 1})
        return 1

    t = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=2))
    assert t.controller_state == ControllerState.INITIALIZING
    result = t.fit()
    assert result.error is None
    assert t.controller_state == ControllerState.FINISHED
    assert ControllerState.RUNNING in t.state_history
    assert t.state_history[0] == ControllerState.INITIALIZING


def test_elastic_downscale_to_available(shutdown_only):
    """num_workers beyond the cluster's CPUs starts elastically with what
    fits (>= min_workers) instead of deadlocking on placement."""
    import ray_tpu
    from ray_tpu.train import JaxTrainer, ScalingConfig, session

    ray_tpu.init(num_cpus=3, num_tpus=0)

    def loop():
        ctx = session.get_context()
        session.report({"world": ctx.get_world_size()})
        return ctx.get_world_size()

    t = JaxTrainer(loop, scaling_config=ScalingConfig(
        num_workers=8, min_workers=1, cpus_per_worker=1))
    result = t.fit()
    assert result.error is None
    world = result.metrics["world"]
    assert 1 <= world <= 3, world  # downscaled to the 3 available CPUs
