"""ray_tpu headline benchmark: Llama train-step throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...diag}.

The north-star target (BASELINE.md) is >=90% of an H100+NCCL stack's
tokens/sec/chip on Llama-2-7B. A single v5e chip cannot hold 7B + optimizer,
so the bench runs a ~1B-param Llama (same architecture, same kernels, bf16,
flash attention, remat scan) and reports **model FLOPs utilization** — the
chip-count- and chip-generation-independent measure of the training stack.
``vs_baseline`` = achieved MFU / 0.45 (0.45 ~= strong H100+NCCL LLM-training
MFU, the normalized form of BASELINE.json's tokens/sec/chip criterion).

Robustness: the driver may run this on a remote-tunneled PJRT platform
("axon") where a mid-flight libtpu upgrade or cold terminal can make one
round pathologically slow (round 1 measured 22x slower than steady-state).
The bench therefore times several independent rounds and reports the best,
and emits per-round diagnostics so a degraded environment is visible in the
artifact instead of masquerading as a framework regression.
"""

from __future__ import annotations

import json
import sys
import time

import os

import jax
import jax.numpy as jnp

PEAK_FLOPS = {
    # bf16 peak per chip
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e
}
BASELINE_MFU = 0.45


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for name, peak in PEAK_FLOPS.items():
        if kind.startswith(name):
            return peak
    return 197e12  # assume v5e


def main() -> None:
    from ray_tpu.models import llama
    from ray_tpu.models.training import (
        ShardedTrainer, default_optimizer, synthetic_batch,
    )
    from ray_tpu.parallel import MeshConfig, make_mesh

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        config = llama.LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=16, num_heads=16, num_kv_heads=16, head_dim=128,
            max_seq_len=2048, remat=True,
            remat_policy=os.environ.get("RAY_TPU_BENCH_REMAT", "full"),
        )
        # Batch sweep on v5e (r5): 4 -> 0.564 MFU, 5 -> 0.568, 6 -> OOM
        # (-379MB; optimizer moments already bf16). Remat sweep: "full"
        # 0.568 > "mlp_only" 0.546 > "attn_out" (r4: worse than full —
        # the flash custom_vjp replays the forward regardless).
        batch_size = int(os.environ.get("RAY_TPU_BENCH_BATCH", 5))
        seq_len = 2048
        rounds, steps_per_round = 3, 5
    else:  # CI fallback so the bench always emits a line
        config = llama.LlamaConfig.tiny()
        batch_size, seq_len = 4, 64
        rounds, steps_per_round = 2, 3

    # Is the pallas flash kernel engaged for this shape (vs XLA fallback)?
    from ray_tpu.ops.attention import flash_applicable
    flash_engaged = bool(
        on_tpu and flash_applicable(seq_len, seq_len, config.head_dim)
    )

    mesh = make_mesh(MeshConfig(fsdp=-1), devices=jax.devices()[:1])
    trainer = ShardedTrainer(
        config, mesh,
        optimizer=default_optimizer(warmup_steps=10, total_steps=1000),
    )
    state = trainer.init_state(0)
    batch = trainer.shard_batch(
        synthetic_batch(batch_size, seq_len, config.vocab_size)
    )

    # Warmup (compile) then timed rounds. Sync via a host fetch of the loss —
    # block_until_ready alone does not flush remote-executed programs on all
    # PJRT backends.
    t0 = time.perf_counter()
    state, metrics = trainer.train_step(state, batch)
    float(metrics["loss"])
    compile_s = time.perf_counter() - t0

    # One extra synced step: measures dispatch+execute+fetch latency, and
    # absorbs any first-execution overhead that follows compilation.
    t0 = time.perf_counter()
    state, metrics = trainer.train_step(state, batch)
    float(metrics["loss"])
    synced_step_s = time.perf_counter() - t0

    round_times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(steps_per_round):
            state, metrics = trainer.train_step(state, batch)
        float(metrics["loss"])
        round_times.append((time.perf_counter() - t0) / steps_per_round)
    step_time = min(round_times)

    cache_misses = None
    try:  # detect silent recompiles during the timed loop
        cache_misses = trainer._step._cache_size()
    except Exception:
        pass

    tokens_per_step = batch_size * seq_len
    tokens_per_sec = tokens_per_step / step_time
    n_params = llama.num_params(config)
    model_flops = 6 * n_params * tokens_per_step  # fwd+bwd, attention excluded
    # add attention flops: 12 * L * H * D * S^2 per batch elem (fwd+bwd, causal)
    attn_flops = (
        12 * config.num_layers * config.num_heads * config.head_dim
        * seq_len * seq_len * batch_size // 2
    )
    flops_per_sec = (model_flops + attn_flops) / step_time
    mfu = flops_per_sec / _peak_flops(jax.devices()[0]) if on_tpu else 0.0

    result = {
        "metric": "llama1b_train_mfu" if on_tpu else "llama_tiny_train_cpu",
        "value": round(mfu, 4) if on_tpu else round(tokens_per_sec, 1),
        "unit": "mfu" if on_tpu else "tokens/s",
        "vs_baseline": round(mfu / BASELINE_MFU, 4) if on_tpu else 0.0,
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "step_time_s": round(step_time, 4),
        "n_params": n_params,
        # diagnostics: if round_times disagree wildly or synced_step >> best
        # round, the *environment* (remote tunnel / libtpu churn) is degraded,
        # not the training stack.
        "round_step_times_s": [round(t, 4) for t in round_times],
        "synced_step_s": round(synced_step_s, 4),
        "compile_s": round(compile_s, 2),
        "flash_kernel": flash_engaged,
        "jit_cache_entries": cache_misses,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
    }
    if max(round_times) > 3 * min(round_times):
        print(
            f"WARNING: unstable round times {round_times} — environment "
            "degradation (tunnel/libtpu churn), rerun advised",
            file=sys.stderr,
        )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
