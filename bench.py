"""ray_tpu headline benchmark: Llama train-step throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...diag}.

The north-star target (BASELINE.md) is >=90% of an H100+NCCL stack's
tokens/sec/chip on Llama-2-7B. A single v5e chip cannot hold 7B + optimizer,
so the bench runs a ~1B-param Llama (same architecture, same kernels, bf16,
flash attention, remat scan) and reports **model FLOPs utilization** — the
chip-count- and chip-generation-independent measure of the training stack.
``vs_baseline`` = achieved MFU / 0.45 (0.45 ~= strong H100+NCCL LLM-training
MFU, the normalized form of BASELINE.json's tokens/sec/chip criterion).

Robustness: the driver may run this on a remote-tunneled PJRT platform
("axon") where a mid-flight libtpu upgrade or cold terminal can make one
round pathologically slow (round 1 measured 22x slower than steady-state).
The bench therefore times several independent rounds and reports the best,
and emits per-round diagnostics so a degraded environment is visible in the
artifact instead of masquerading as a framework regression.
"""

from __future__ import annotations

import json
import sys
import time

import os

import jax
import jax.numpy as jnp

PEAK_FLOPS = {
    # bf16 peak per chip
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e
}
BASELINE_MFU = 0.45


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for name, peak in PEAK_FLOPS.items():
        if kind.startswith(name):
            return peak
    return 197e12  # assume v5e


def _train_flops(config, n_params: int, n_batch: int, seq_len: int) -> int:
    """fwd+bwd FLOPs for one step: 6*P per token, plus causal attention
    12 * L * H * D * S^2 / 2 per batch element. Single source of truth —
    the headline MFU and the microbatch sweep must stay comparable."""
    model = 6 * n_params * n_batch * seq_len
    attn = (12 * config.num_layers * config.num_heads * config.head_dim
            * seq_len * seq_len * n_batch // 2)
    return model + attn


def main() -> None:
    from ray_tpu.models import llama
    from ray_tpu.models.training import (
        ShardedTrainer, default_optimizer, synthetic_batch,
    )
    from ray_tpu.parallel import MeshConfig, make_mesh

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        config = llama.LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_layers=16, num_heads=16, num_kv_heads=16, head_dim=128,
            max_seq_len=2048, remat=True,
            remat_policy=os.environ.get("RAY_TPU_BENCH_REMAT", "full"),
        )
        # Batch sweep on v5e (r5): 4 -> 0.564 MFU, 5 -> 0.568, 6 -> OOM
        # (-379MB; optimizer moments already bf16). Remat sweep: "full"
        # 0.568 > "mlp_only" 0.546 > "attn_out" (r4: worse than full —
        # the flash custom_vjp replays the forward regardless).
        batch_size = int(os.environ.get("RAY_TPU_BENCH_BATCH", 5))
        seq_len = 2048
        rounds, steps_per_round = 3, 5
    else:  # CI fallback so the bench always emits a line
        config = llama.LlamaConfig.tiny()
        batch_size, seq_len = 4, 64
        rounds, steps_per_round = 2, 3

    # Is the pallas flash kernel engaged for this shape (vs XLA fallback)?
    from ray_tpu.ops.attention import flash_applicable
    flash_engaged = bool(
        on_tpu and flash_applicable(seq_len, seq_len, config.head_dim)
    )

    mesh = make_mesh(MeshConfig(fsdp=-1), devices=jax.devices()[:1])
    trainer = ShardedTrainer(
        config, mesh,
        optimizer=default_optimizer(warmup_steps=10, total_steps=1000),
    )
    state = trainer.init_state(0)
    batch = trainer.shard_batch(
        synthetic_batch(batch_size, seq_len, config.vocab_size)
    )

    # Warmup (compile) then timed rounds. Sync via a host fetch of the loss —
    # block_until_ready alone does not flush remote-executed programs on all
    # PJRT backends.
    t0 = time.perf_counter()
    state, metrics = trainer.train_step(state, batch)
    float(metrics["loss"])
    compile_s = time.perf_counter() - t0

    # One extra synced step: measures dispatch+execute+fetch latency, and
    # absorbs any first-execution overhead that follows compilation.
    t0 = time.perf_counter()
    state, metrics = trainer.train_step(state, batch)
    float(metrics["loss"])
    synced_step_s = time.perf_counter() - t0

    round_times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(steps_per_round):
            state, metrics = trainer.train_step(state, batch)
        float(metrics["loss"])
        round_times.append((time.perf_counter() - t0) / steps_per_round)
    step_time = min(round_times)

    cache_misses = None
    try:  # detect silent recompiles during the timed loop
        cache_misses = trainer._step._cache_size()
    except Exception:
        pass

    # ---- input pipeline: prefetch off vs on ------------------------------
    # "Off" reproduces the r05 real-loop shape: host batch assembly +
    # synchronous shard_batch + a per-step loss fetch, all inside the
    # step loop. "On" stages batches through the DevicePrefetcher's
    # background double/triple buffer and drives the AsyncStepLoop with
    # windowed metric fetches — the configuration the gap acceptance
    # (synced_step_s - step_time_s cut >=2x, stall fraction <5%) grades.
    from ray_tpu.train.ingest import DevicePrefetcher, synthetic_host_batches
    from ray_tpu.train.loop import AsyncStepLoop

    pipe_steps = rounds * steps_per_round
    t0 = time.perf_counter()
    for hb in synthetic_host_batches(batch_size, seq_len,
                                     config.vocab_size, pipe_steps):
        state, metrics = trainer.train_step(state, trainer.shard_batch(hb))
        float(metrics["loss"])
    host_loop_step_s = (time.perf_counter() - t0) / pipe_steps

    pf = DevicePrefetcher(
        synthetic_host_batches(batch_size, seq_len, config.vocab_size,
                               pipe_steps + 1),
        trainer, depth=3, name="bench")
    loop = AsyncStepLoop(trainer, state, sync_every=4, name="bench")
    loop.step(next(pf))   # warm the window + fill the buffer...
    loop.sync()
    pf.reset_stats()      # ...then measure steady state only
    t0 = time.perf_counter()
    state, _ = loop.run(pf)
    pipe_wall = time.perf_counter() - t0
    pipelined_step_s = pipe_wall / pipe_steps
    stall = pf.stats()
    pf.close()
    n_params = llama.num_params(config)

    # ---- gradient-accumulation microbatch sweep (M in {1, 2, 4}) ---------
    # Global batch fixed (largest multiple of 4 <= batch_size) so the
    # three points compare step time at IDENTICAL tokens/step; the carry
    # accumulates in the params' dtype to keep HBM flat. OOM at a sweep
    # point is reported, not fatal — the headline metric stands alone.
    # Free the headline trainer first: on TPU the 1B headline sits within
    # ~400MB of OOM, so a sweep point's second params+optimizer copy only
    # fits once state/loop/batch drop their references.
    state = batch = loop = trainer = None
    sweep_global = max(4, batch_size - batch_size % 4)
    microbatch_sweep = []
    for m_count in (1, 2, 4):
        entry = {"microbatches": m_count,
                 "global_batch": sweep_global,
                 "micro_batch": sweep_global // m_count}
        try:
            tr_m = ShardedTrainer(
                config, mesh,
                optimizer=default_optimizer(warmup_steps=10,
                                            total_steps=1000),
                microbatches=m_count, grad_accum_dtype=config.dtype)
            st_m = tr_m.init_state(0)
            b_m = tr_m.shard_batch(
                synthetic_batch(sweep_global, seq_len, config.vocab_size))
            st_m, mm = tr_m.train_step(st_m, b_m)   # compile
            float(mm["loss"])
            t0 = time.perf_counter()
            for _ in range(steps_per_round):
                st_m, mm = tr_m.train_step(st_m, b_m)
            float(mm["loss"])
            m_step = (time.perf_counter() - t0) / steps_per_round
            m_tokens_s = sweep_global * seq_len / m_step
            entry["step_time_s"] = round(m_step, 4)
            entry["tokens_per_sec_per_chip"] = round(m_tokens_s, 1)
            if on_tpu:
                m_flops = _train_flops(config, n_params, sweep_global,
                                       seq_len)
                entry["mfu"] = round(
                    m_flops / m_step / _peak_flops(jax.devices()[0]), 4)
        except Exception as e:  # noqa: BLE001 — typically OOM at 1B
            entry["error"] = f"{type(e).__name__}: {str(e)[:200]}"
        finally:
            # Drop the point's state either way: an OOM'd point must not
            # keep its params+optimizer moments alive into the next M.
            st_m = tr_m = b_m = mm = None
        microbatch_sweep.append(entry)

    # ---- RL post-training loop: weight-sync + rollout phase --------------
    # Generate → publish → subscribe → tick-boundary swap on a tiny llama
    # through the REAL engine and sync plane (ray_tpu/rl): per-sync
    # latency p50/p95 (publish through swapped-live), sync bytes/s,
    # rollout staleness, and tokens generated between syncs. Gated: an
    # rl_loop failure reports in the artifact, never sinks the headline.
    rl_loop = {}
    try:
        import numpy as np

        from ray_tpu.models.continuous_batching import ContinuousBatcher
        from ray_tpu.rl import (RolloutScheduler, WeightPublisher,
                                WeightSubscriber)

        tiny = llama.LlamaConfig.tiny()
        rl_tokens: dict = {}
        eng = ContinuousBatcher(
            tiny, num_slots=4, max_len=64,
            token_callback=lambda rid, t:
                rl_tokens.setdefault(rid, []).append(t))
        pub = WeightPublisher(run="bench_rl", n_subscribers=1)
        sub = WeightSubscriber(pub.subscriber_spec(0), run="bench_rl")

        def rl_generate(prompt, max_new):
            rid = eng.submit(list(prompt), max_new_tokens=max_new)
            while True:
                if rid in eng.step():
                    break
            out = rl_tokens.pop(rid, [])
            lps = (np.asarray(eng.score_logprobs(prompt, out), np.float32)
                   if out else np.zeros(0, np.float32))
            return out, lps, eng.weight_version

        sched = RolloutScheduler(rl_generate, lambda: pub.version,
                                 run="bench_rl")
        sync_times, tokens_between, total_bytes = [], [], 0
        rl_rounds, rl_prompts, rl_new = 4, 2, 8
        for r in range(rl_rounds):
            n = sched.collect([[1 + r, 2, 3]] * rl_prompts, rl_new,
                              lambda p, t: float(len(t)))
            tokens_between.append(n * rl_new)
            faked = jax.tree.map(lambda a: (a * 0.999).astype(a.dtype),
                                 eng.params)
            t0 = time.perf_counter()
            manifest = pub.publish(faked, step=r)
            got = sub.poll(timeout=5.0)
            if got is not None:
                m, params = got
                eng.swap_params(params, version=int(m["version"]))
            sync_times.append(time.perf_counter() - t0)
            total_bytes += manifest["bytes"]
        sync_times.sort()
        staleness = sched.buffer.staleness()
        rl_loop = {
            "sync_p50_s": round(sync_times[len(sync_times) // 2], 5),
            "sync_p95_s": round(sync_times[-1], 5),
            "sync_bytes_per_s": round(
                total_bytes / max(sum(sync_times), 1e-9), 1),
            "rollout_staleness_max": max(staleness) if staleness else 0,
            "tokens_between_syncs": (
                sum(tokens_between) / len(tokens_between)),
            "generator_version": eng.weight_version,
            "trainer_version": pub.version,
        }
        pub.destroy()
    except Exception as e:  # noqa: BLE001 — report, don't sink the bench
        rl_loop = {"error": f"{type(e).__name__}: {str(e)[:200]}"}

    tokens_per_step = batch_size * seq_len
    tokens_per_sec = tokens_per_step / step_time
    flops_per_sec = (
        _train_flops(config, n_params, batch_size, seq_len) / step_time)
    mfu = flops_per_sec / _peak_flops(jax.devices()[0]) if on_tpu else 0.0

    result = {
        "metric": "llama1b_train_mfu" if on_tpu else "llama_tiny_train_cpu",
        "value": round(mfu, 4) if on_tpu else round(tokens_per_sec, 1),
        "unit": "mfu" if on_tpu else "tokens/s",
        "vs_baseline": round(mfu / BASELINE_MFU, 4) if on_tpu else 0.0,
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "step_time_s": round(step_time, 4),
        "n_params": n_params,
        # diagnostics: if round_times disagree wildly or synced_step >> best
        # round, the *environment* (remote tunnel / libtpu churn) is degraded,
        # not the training stack.
        "round_step_times_s": [round(t, 4) for t in round_times],
        "synced_step_s": round(synced_step_s, 4),
        # Input pipeline: the host-in-loop gap vs the prefetch+async gap
        # (per-step overhead above the pure device step time). Acceptance:
        # pipelined_gap_s <= synced_gap_s / 2 and input_stall_frac < 0.05.
        "host_loop_step_s": round(host_loop_step_s, 4),
        "pipelined_step_s": round(pipelined_step_s, 4),
        "synced_gap_s": round(synced_step_s - step_time, 4),
        "host_loop_gap_s": round(host_loop_step_s - step_time, 4),
        "pipelined_gap_s": round(pipelined_step_s - step_time, 4),
        "input_stall_frac": round(stall["input_stall_frac"], 4),
        "ingest_bytes_per_s": round(stall["bytes_per_s"], 1),
        "prefetch_avg_occupancy": round(stall["avg_occupancy"], 3),
        "tokens_per_sec_per_chip_pipelined": round(
            tokens_per_step / pipelined_step_s, 1),
        "microbatch_sweep": microbatch_sweep,
        "rl_loop": rl_loop,
        "compile_s": round(compile_s, 2),
        "flash_kernel": flash_engaged,
        "jit_cache_entries": cache_misses,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
    }
    if max(round_times) > 3 * min(round_times):
        print(
            f"WARNING: unstable round times {round_times} — environment "
            "degradation (tunnel/libtpu churn), rerun advised",
            file=sys.stderr,
        )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
